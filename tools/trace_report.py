#!/usr/bin/env python
"""Render mxtpu.telemetry trace runs (docs/OBSERVABILITY.md "Tracing &
flight recorder").

Summary mode — assembles the ``kind:"trace"`` span records of a JSONL
run into per-trace trees and prints, per root span name, the request
count, p50/p99 wall time, and the critical-path breakdown over the
child spans. Decode traces additionally get the TTFT decomposition:
p50/p99 time-to-first-token split into queue + prefill + join (the
contiguous segments of the critical path; the residual is scheduler
overhead between them), checked against the measured ``ttft_ms`` the
session annotated on the root::

    python tools/trace_report.py run.jsonl

Compare mode — per-segment deltas between two runs (the same shape as
``telemetry_report.py --compare``)::

    python tools/trace_report.py --compare a.jsonl b.jsonl

Flight-recorder dumps (``flight-*.json``) are accepted anywhere a JSONL
path is: the dump's ``spans`` list is read instead.

Only stdlib + the sibling package's tolerant reader are used, so this
runs on a box without jax installed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: root-span names whose traces get the TTFT decomposition treatment
DECODE_ROOTS = ("decode.request",)

#: segments of the decode TTFT critical path, in wall order. They are
#: recorded back-to-back on one perf_counter clock, so their sum plus a
#: small scheduler residual IS the measured TTFT.
TTFT_SEGMENTS = ("queue", "prefill", "join")


def _read(path: str) -> List[Dict]:
    if path.endswith(".json"):        # a flight-recorder dump
        with open(path) as f:
            payload = json.load(f)
        return list(payload.get("spans", []))
    try:
        from incubator_mxnet_tpu.telemetry import read_jsonl

        return read_jsonl(path)
    except ImportError:          # jax-less box: inline the tolerant reader
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
        return out


def _select_run(records: List[Dict], merge: bool = False):
    """Newest ``run_start``-delimited segment (see telemetry_report)."""
    if merge:
        return [r for r in records if r.get("kind") != "run_start"], 0
    runs: List[List[Dict]] = [[]]
    for r in records:
        if r.get("kind") == "run_start":
            runs.append([])
        else:
            runs[-1].append(r)
    runs = [seg for seg in runs if seg]
    if not runs:
        return [], 0
    return runs[-1], len(runs) - 1


def _pctl(vals: List[float], p: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, max(0, int(round(p / 100.0 * len(s))) - 1))]


# -- trace assembly ---------------------------------------------------------
def _span_records(records: List[Dict]) -> List[Dict]:
    """The finished-span records: ``kind:"trace"`` rows carrying a span
    id (the dump/trigger event rows carry ``event`` instead)."""
    return [r for r in records
            if r.get("kind") == "trace" and "span" in r]


def _event_records(records: List[Dict]) -> List[Dict]:
    return [r for r in records
            if r.get("kind") == "trace" and "event" in r]


def assemble(records: List[Dict]) -> Dict[str, Dict]:
    """Group span records into traces: ``trace_id -> {"root": rec|None,
    "spans": [rec...], "children": {name: [rec...]}}``. A trace whose
    root never finished (crash, torn tail) still appears with
    ``root=None`` — its children remain reportable."""
    traces: Dict[str, Dict] = {}
    for rec in _span_records(records):
        tid = rec.get("trace")
        if tid is None:
            continue
        tr = traces.setdefault(
            tid, {"root": None, "spans": [], "children": {}})
        tr["spans"].append(rec)
        if rec.get("parent") is None:
            tr["root"] = rec
        else:
            tr["children"].setdefault(
                rec.get("name", "?"), []).append(rec)
    return traces


def _traces_by_root(traces: Dict[str, Dict]) -> Dict[str, List[Dict]]:
    by_root: Dict[str, List[Dict]] = {}
    for tr in traces.values():
        name = tr["root"].get("name", "?") if tr["root"] else "(no root)"
        by_root.setdefault(name, []).append(tr)
    return by_root


def _child_durs(trs: List[Dict], name: str) -> List[float]:
    """One duration per trace for child span ``name`` (a trace with
    several same-named children — per-step spans — contributes their
    sum: the critical path charges the whole family)."""
    out = []
    for tr in trs:
        recs = tr["children"].get(name)
        if recs:
            out.append(sum(float(r.get("dur_ms", 0.0)) for r in recs))
    return out


def _child_names(trs: List[Dict]) -> List[str]:
    names: List[str] = []
    for tr in trs:
        for name in tr["children"]:
            if name not in names:
                names.append(name)
    return names


# -- TTFT decomposition -----------------------------------------------------
def ttft_decomposition(trs: List[Dict]) -> Optional[Dict]:
    """Per-segment p50/p99 of the decode TTFT critical path, the
    measured TTFT the session annotated on each root, and the residual
    (measured minus the segment sum — scheduler overhead between the
    contiguous segments). Only traces carrying a measured ``ttft_ms``
    AND every segment participate, so the sums are apples-to-apples.
    None when no trace qualifies."""
    rows: List[Dict[str, float]] = []
    for tr in trs:
        root = tr["root"]
        if root is None or "ttft_ms" not in root:
            continue
        segs = {}
        for name in TTFT_SEGMENTS:
            recs = tr["children"].get(name)
            if not recs:
                break
            segs[name] = sum(float(r.get("dur_ms", 0.0)) for r in recs)
        else:
            segs["ttft_ms"] = float(root["ttft_ms"])
            segs["residual"] = segs["ttft_ms"] - sum(
                segs[n] for n in TTFT_SEGMENTS)
            rows.append(segs)
    if not rows:
        return None
    out: Dict = {"n": len(rows)}
    for key in TTFT_SEGMENTS + ("residual", "ttft_ms"):
        vals = [r[key] for r in rows]
        out[key] = {"p50": _pctl(vals, 50), "p99": _pctl(vals, 99)}
    return out


def summarize(path: str, merge: bool = False) -> str:
    records, skipped = _select_run(_read(path), merge=merge)
    traces = assemble(records)
    head = f"trace report — {path} ({len(traces)} traces"
    if skipped:
        head += f"; newest of {skipped + 1} runs, --all merges"
    lines = [head + ")"]
    by_root = _traces_by_root(traces)
    for root_name in sorted(by_root):
        trs = by_root[root_name]
        root_durs = [float(tr["root"].get("dur_ms", 0.0))
                     for tr in trs if tr["root"]]
        errs = sum(1 for tr in trs
                   if tr["root"] and "error" in tr["root"])
        lines.append("")
        lines.append(
            f"{root_name}: {len(trs)} traces, "
            f"p50 {_pctl(root_durs, 50):.2f} ms / "
            f"p99 {_pctl(root_durs, 99):.2f} ms"
            + (f", {errs} error(s)" if errs else ""))
        lines.append(f"  {'span':20s} {'traces':>7s} {'p50 ms':>10s} "
                     f"{'p99 ms':>10s} {'% of root p50':>14s}")
        root_p50 = _pctl(root_durs, 50)
        for name in _child_names(trs):
            durs = _child_durs(trs, name)
            p50 = _pctl(durs, 50)
            share = f"{100.0 * p50 / root_p50:13.1f}%" \
                if root_p50 > 0 else f"{'-':>14s}"
            lines.append(f"  {name:20s} {len(durs):7d} {p50:10.3f} "
                         f"{_pctl(durs, 99):10.3f} {share}")
        if root_name in DECODE_ROOTS:
            dec = ttft_decomposition(trs)
            if dec:
                lines.append(f"  TTFT decomposition "
                             f"({dec['n']} requests):")
                lines.append(f"    {'segment':18s} {'p50 ms':>10s} "
                             f"{'p99 ms':>10s}")
                for key in TTFT_SEGMENTS + ("residual",):
                    lines.append(f"    {key:18s} "
                                 f"{dec[key]['p50']:10.3f} "
                                 f"{dec[key]['p99']:10.3f}")
                lines.append(f"    {'= measured TTFT':18s} "
                             f"{dec['ttft_ms']['p50']:10.3f} "
                             f"{dec['ttft_ms']['p99']:10.3f}")
    events = _event_records(records)
    dumps = [r for r in events if r.get("event") == "dump"]
    trig = [r for r in events if r.get("event") == "trigger"]
    if dumps or trig:
        lines.append("")
        for r in dumps:
            lines.append(f"flight dump [{r.get('reason', '?')}]: "
                         f"{r.get('path', '?')}")
        for r in trig:
            cap = "captured" if r.get("captured") else "NOT captured"
            lines.append(
                f"trigger [{r.get('reason', '?')}] at "
                f"{r.get('site') or '?'}"
                + (f" ({r.get('detail')})" if r.get("detail") else "")
                + f": {cap}"
                + (f" -> {r['profile_dir']}"
                   if r.get("profile_dir") else ""))
    if len(lines) == 1:
        lines.append("")
        lines.append("no trace spans in this run — sampling off? "
                     "(MXTPU_TRACE_SAMPLE, default 0)")
    return "\n".join(lines)


def _comparable_metrics(records: List[Dict]) -> Dict[str, float]:
    """Flatten a run's traces into {key: value} for diffing: per-root
    counts and p50/p99, per-child p50, TTFT segment p50s."""
    out: Dict[str, float] = {}
    by_root = _traces_by_root(assemble(records))
    for root_name, trs in by_root.items():
        base = f"trace/{root_name}"
        root_durs = [float(tr["root"].get("dur_ms", 0.0))
                     for tr in trs if tr["root"]]
        out[f"{base}/traces"] = float(len(trs))
        if root_durs:
            out[f"{base}/p50_ms"] = _pctl(root_durs, 50)
            out[f"{base}/p99_ms"] = _pctl(root_durs, 99)
        for name in _child_names(trs):
            durs = _child_durs(trs, name)
            if durs:
                out[f"{base}/{name}/p50_ms"] = _pctl(durs, 50)
        if root_name in DECODE_ROOTS:
            dec = ttft_decomposition(trs)
            if dec:
                for key in TTFT_SEGMENTS + ("residual", "ttft_ms"):
                    out[f"{base}/ttft/{key}_p50_ms"] = dec[key]["p50"]
    for r in _event_records(records):
        ev = r.get("event", "?")
        key = f"trace_events/{ev}"
        out[key] = out.get(key, 0.0) + 1.0
    return out


def compare(path_a: str, path_b: str, merge: bool = False) -> str:
    a = _comparable_metrics(_select_run(_read(path_a), merge=merge)[0])
    b = _comparable_metrics(_select_run(_read(path_b), merge=merge)[0])
    keys = sorted(set(a) | set(b))
    lines = [f"trace compare — A={path_a}  B={path_b}",
             "",
             f"{'metric':52s} {'A':>12s} {'B':>12s} {'delta':>9s}"]
    for k in keys:
        va, vb = a.get(k), b.get(k)
        if va is None or vb is None:
            lines.append(f"{k:52s} "
                         f"{'-' if va is None else format(va, '12.3f'):>12s} "
                         f"{'-' if vb is None else format(vb, '12.3f'):>12s} "
                         f"{'only ' + ('B' if va is None else 'A'):>9s}")
            continue
        if va:
            delta = f"{100.0 * (vb - va) / abs(va):+8.1f}%"
        else:
            delta = "   n/a" if vb == 0 else "   new"
        lines.append(f"{k:52s} {va:12.3f} {vb:12.3f} {delta:>9s}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render mxtpu trace runs (spans, TTFT decomposition, "
                    "flight dumps)")
    ap.add_argument("paths", nargs="*",
                    help="one JSONL run (or flight-*.json dump)")
    ap.add_argument("--compare", nargs=2, metavar=("A", "B"),
                    help="diff two runs per trace segment")
    ap.add_argument("--all", action="store_true",
                    help="merge every run in the file instead of only "
                         "the newest")
    args = ap.parse_args(argv)
    if args.compare:
        print(compare(*args.compare, merge=args.all))
        return 0
    if len(args.paths) != 1:
        ap.error("pass exactly one JSONL path, or --compare A B")
    print(summarize(args.paths[0], merge=args.all))
    return 0


if __name__ == "__main__":
    sys.exit(main())
